#!/usr/bin/env python3
"""Tier-1 master-HA smoke (wired into scripts/run_tier1.sh).

Runs a tiny 2-process lockstep mnist job on the CPU backend under the
``master_kill_mid_epoch`` chaos plan with master high availability ON
(``--master_journal_dir``), i.e. SIGKILL the master mid-epoch, relaunch
it from the control-plane journal, and require:

1. the job completes and the chaos report's invariants all PASS
   (including ``master_recovery``: a journal replay per extra master
   life and a monotone generation fence spanning the outage);
2. the master was actually killed and relaunched (``master_lives >= 2``);
3. the span log records the recovery itself: a ``master_restart`` span
   for the second life, a ``journal_replay`` child, and at least one
   ``worker_rehome`` handshake — the workers outlived the master rather
   than dying on the first failed RPC.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    import tempfile

    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_JOURNAL_REPLAY,
        SPAN_MASTER_RESTART,
        SPAN_WORKER_REHOME,
        SPANS_FILENAME,
        read_spans,
    )

    with tempfile.TemporaryDirectory() as workdir:
        report = run_chaos_job(
            ChaosJobConfig(
                plan=named_plan("master_kill_mid_epoch", 2),
                workdir=os.path.join(workdir, "chaos"),
                num_records=256,
                num_epochs=2,
                num_workers=2,
                master_ha=True,
                run_timeout_secs=300.0,
            )
        )
        failed = [
            i["name"]
            for i in report["invariants"]
            if i["status"] != "PASS"
        ]
        if not report["invariants_ok"] or failed:
            print(
                f"master_ha_smoke: invariants failed: {failed} "
                f"(rc={report.get('rc')}, timed_out="
                f"{report.get('timed_out')})",
                file=sys.stderr,
            )
            return 1
        names = [i["name"] for i in report["invariants"]]
        if "master_recovery" not in names:
            print(
                "master_ha_smoke: master_recovery invariant missing "
                "from the report",
                file=sys.stderr,
            )
            return 1
        lives = report.get("master_lives", 0)
        if lives < 2:
            print(
                f"master_ha_smoke: master_lives={lives} — the master "
                "was never killed and relaunched",
                file=sys.stderr,
            )
            return 1
        spans = read_spans(
            os.path.join(workdir, "chaos", "telemetry", SPANS_FILENAME)
        )
        by_name = {}
        for s in spans:
            by_name.setdefault(s.get("span"), []).append(s)
        for required in (
            SPAN_MASTER_RESTART,
            SPAN_JOURNAL_REPLAY,
            SPAN_WORKER_REHOME,
        ):
            if not by_name.get(required):
                print(
                    f"master_ha_smoke: no {required} span — the "
                    "recovery left no trace evidence",
                    file=sys.stderr,
                )
                return 1
        rehomes = len(by_name[SPAN_WORKER_REHOME])
    print(
        f"master_ha_smoke: OK (master_lives={lives}, "
        f"{rehomes} worker re-home handshake(s) recorded)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
