#!/usr/bin/env python3
"""Tier-1 SLO watchdog smoke (wired into scripts/run_tier1.sh).

End-to-end falsifiable story for the watchdog plane (telemetry/slo.py +
telemetry/incident.py): a REAL training run with an injected
input-pipeline regression must be caught, attributed, profiled, and
postmortemed — and a silenced watchdog must fail the fleet gate.

1. **Injected regression** — a workdir model-zoo module re-exports the
   builtin mnist spec but its ``dataset_fn`` sleeps per record over the
   middle ~third of the stream; the single-threaded host pipeline
   serializes the sleeps, so the instrumented LocalExecutor run's
   ``step_anatomy`` events show ``host_fetch`` dominating exactly that
   window (the injection seam is itself gated: healthy head for the
   auto-baseline, slow middle, healthy tail for recovery).
2. **Burn-rate verdict** — the SAME engine the master runs replays the
   run's measured signals on an injectable clock (one heartbeat-cadence
   tick per dispatch, the shared ``StepTimePercentileTracker`` fed from
   the run's real step cadence): the step-time objective fires exactly
   ONCE (multi-window burn + hysteresis — no flap on the healthy tail),
   flips the ``/healthz`` ``slo`` block, auto-arms ``request_profile``
   on a real MasterServicer, opens exactly ONE incident, and recovers
   exactly once, closing it.
3. **Postmortem artifact** — ``incidents/incident_1.json`` parses, its
   ``suspected_cause`` is ``input-bound`` with ``host_fetch`` named in
   the rationale (the injected phase, attributed from the anatomy
   deltas across the incident window), and it points at the armed
   profile window.
4. **Auto-armed capture** — the armed window rides a real heartbeat
   down, arms the worker-side ``StepProfiler`` through
   ``apply_profile_command``, and a short jitted loop produces capture
   artifacts + ``profile_window_open``/``close`` events for the SAME
   window id the incident recorded; a replayed command is absorbed.
5. **Report + falsification** — ``telemetry.report``'s machine summary
   over the watchdog's event log reaches the ``degraded`` verdict (one
   incident, recovered, input-bound), and a small-world fleetsim run
   with ``--corrupt mute_slo`` (detectors silenced) exits 1 with the
   ``slo_detection`` invariant FAILED — the gate is falsifiable both
   ways.

The disabled path (``--slo_config`` unset -> no engine, byte-identical
argv/behavior) is pinned by tests/test_slo.py, not here.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# 36 one-step dispatches: 12 healthy (baseline), ~8 slow (burn), ~16
# healthy (recovery) — the detector timeline below is derived from this
NUM_RECORDS = 2304
MINIBATCH = 64
RECORDS_PER_TASK = 576
# records (768, 1280] each sleep 100ms in the parse: ~6.4s/batch against
# a sub-second healthy step, far past the 3x auto-baseline factor.  The
# margin is deliberately wide: the incident's open->close anatomy delta
# must stay host_fetch-dominated (input-bound) even when a loaded CI
# host inflates the real device-compute times by an order of magnitude
SLOW_AFTER_RECORDS = 768
SLOW_UNTIL_RECORDS = 1280
SLEEP_SECS = 0.100
# a dispatch whose fetch wait exceeds this is "slow" (healthy fetches
# are tens of ms; injected ones are seconds)
SLOW_FETCH_MS = 1000.0
# the replay evaluates once per dispatch on a virtual heartbeat cadence
TICK_SECS = 10.0
# short percentile window so the healthy tail evicts the burn and the
# detector can watch the run RECOVER within 36 dispatches
TRACKER_WINDOW = 8

# the declarative config under test: one objective (step-time p95 vs a
# learned baseline) so "exactly one violation" is exact, not modulo
# which objectives happened to join
SLO_CONFIG = {
    "objectives": [
        {
            "name": "step_time_p95",
            "signal": "step_time_p95_ms",
            "comparator": "above",
            "baseline_factor": 3.0,
        }
    ],
    "profile_steps": 4,
}

ZOO_MODULE = '''\
"""Mnist zoo module with a deterministic input-pipeline regression.

Re-exports the builtin mnist spec but replaces ``dataset_fn`` with a
parse that sleeps per record over a middle window of the stream.  No
``batch_parse``/``shuffle``: the per-element path is lazy, so each
sleep lands in the host fetch wait of the batch that consumes it
(a shuffle buffer would front-load the whole window into one fetch).
"""

import time

import numpy as np

from elasticdl_tpu.data.reader import decode_example
from elasticdl_tpu.models.mnist_functional_api import (  # noqa: F401
    custom_model,
    eval_metrics_fn,
    loss,
    optimizer,
)
from elasticdl_tpu.trainer.state import Modes

SLOW_AFTER = {slow_after}
SLOW_UNTIL = {slow_until}
SLEEP_SECS = {sleep_secs}

_parsed = 0


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        global _parsed
        if mode == Modes.TRAINING:
            _parsed += 1
            if SLOW_AFTER < _parsed <= SLOW_UNTIL:
                time.sleep(SLEEP_SECS)
        ex = decode_example(record)
        image = ex["image"].astype(np.float32) / 255.0
        if mode == Modes.PREDICTION:
            return {{"image": image}}
        return {{"image": image}}, ex["label"].astype(np.int32)

    return dataset.map(_parse)
'''


def _fail(message: str) -> int:
    print(f"slo_smoke: {message}", file=sys.stderr)
    return 1


class _Clock:
    """Settable clock for the replay (engine + tracker are clock-
    injectable by contract)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _train_with_regression(workdir: str) -> int | list:
    """Gate 1: instrumented run through the injected-slowdown zoo
    module; returns the dispatch-ordered step_anatomy events."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.telemetry import anatomy as anatomy_mod
    from elasticdl_tpu.telemetry import tracing, worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    zoo = os.path.join(workdir, "zoo")
    os.makedirs(zoo)
    with open(
        os.path.join(zoo, "slow_input_mnist.py"), "w", encoding="utf-8"
    ) as f:
        f.write(
            ZOO_MODULE.format(
                slow_after=SLOW_AFTER_RECORDS,
                slow_until=SLOW_UNTIL_RECORDS,
                sleep_secs=SLEEP_SECS,
            )
        )
    train = synthetic.gen_mnist(
        os.path.join(workdir, "train"),
        num_records=NUM_RECORDS,
        num_shards=1,
        seed=17,
    )
    telemetry_dir = os.path.join(workdir, "telemetry")
    args = parse_master_args(
        [
            "--model_zoo",
            zoo,
            "--model_def",
            "slow_input_mnist.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            str(MINIBATCH),
            "--records_per_task",
            str(RECORDS_PER_TASK),
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
            "--steps_per_dispatch",
            "1",
            "--telemetry_dir",
            telemetry_dir,
            "--step_anatomy",
            "true",
        ]
    )
    try:
        LocalExecutor(args).run()
    finally:
        anatomy_mod.uninstall()
        worker_hooks.uninstall()
        tracing.uninstall()

    events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
    anat = sorted(
        (e for e in events if e.get("event") == "step_anatomy"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    expected = NUM_RECORDS // MINIBATCH
    if len(anat) < expected - 2:
        return _fail(
            f"only {len(anat)} step_anatomy dispatches (expected "
            f"~{expected})"
        )
    slow = [
        i
        for i, e in enumerate(anat, 1)
        if float(e.get("host_fetch_ms", 0.0)) > SLOW_FETCH_MS
    ]
    if len(slow) < 4:
        return _fail(
            f"injected regression not visible: only {len(slow)} "
            f"dispatches with host_fetch > {SLOW_FETCH_MS}ms"
        )
    # detector preconditions this injection shape must provide: enough
    # healthy head for the auto-baseline (p95 warmup + baseline evals
    # resolve at dispatch 9) and enough healthy tail to evict the burn
    # from the percentile window and clear the fast window
    if slow[0] < 11:
        return _fail(
            f"regression onset at dispatch {slow[0]} — too early for "
            "the auto-baseline to have resolved (need >= 11)"
        )
    if len(anat) - slow[-1] < TRACKER_WINDOW + 3:
        return _fail(
            f"only {len(anat) - slow[-1]} healthy dispatches after the "
            f"regression (need >= {TRACKER_WINDOW + 3} for recovery)"
        )
    # the injected phase is host_fetch, not the device path
    for i in slow:
        e = anat[i - 1]
        device = (
            float(e.get("assemble_ms", 0.0))
            + float(e.get("h2d_transfer_ms", 0.0))
            + float(e.get("device_compute_ms", 0.0))
        )
        if float(e.get("host_fetch_ms", 0.0)) <= device:
            return _fail(
                f"slow dispatch {i}: host_fetch "
                f"{e.get('host_fetch_ms'):.0f}ms did not dominate the "
                f"device path ({device:.0f}ms)"
            )
    return anat


def _watchdog_verdict(workdir: str, anat: list) -> int | dict:
    """Gates 2+3: the real engine over the run's measured signals —
    one violation, one incident, one auto-armed window, one recovery,
    and a parsing postmortem that attributes the injected phase."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry import slo as slo_mod
    from elasticdl_tpu.telemetry.incident import (
        IncidentManager,
        read_incidents,
    )
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    watchdog_dir = os.path.join(workdir, "watchdog")
    os.makedirs(watchdog_dir)
    dispatcher = TaskDispatcher(
        {"s": (0, 64)}, records_per_task=64, num_epochs=1
    )
    servicer = MasterServicer(64, dispatcher)
    telemetry = MasterTelemetry(telemetry_dir=watchdog_dir)
    telemetry.attach(dispatcher, servicer)

    eval_clock = _Clock()  # virtual heartbeat cadence
    data_clock = _Clock()  # the run's real step cadence
    # cumulative fleet-style phase totals rebuilt from the run's events
    # (the master snapshots servicer.phase_stats_totals(); the replay
    # holds the same shape at each tick)
    cum: dict = {}

    def context() -> dict:
        return {"anatomy": {k: dict(v) for k, v in cum.items()}}

    incidents = IncidentManager(
        telemetry_dir=watchdog_dir,
        emit=telemetry.events.emit,
        clock=eval_clock,
        context_fn=context,
    )
    armed: list[int] = []

    def arm_profiler(num_steps: int):
        # the master's _slo_arm_profiler idiom: request_profile on the
        # real servicer, attach the window to the open incident
        response = servicer.request_profile(
            msg.RequestProfileRequest(num_steps=num_steps)
        )
        if response.accepted:
            incidents.note_profile_window(
                {"window_id": response.window_id}
            )
            armed.append(response.window_id)

    engine = slo_mod.SLOEngine(
        slo_mod.parse_slo_config(json.dumps(SLO_CONFIG)),
        clock=eval_clock,
        emit=telemetry.events.emit,
        tracer=telemetry.tracer,
        arm_profiler=arm_profiler,
        incidents=incidents,
    )
    # the shared-tracker wiring: THE percentile definition site, fed
    # from the run's real step cadence (short window so the healthy
    # tail can evict the burn within this run's length)
    engine.tracker = slo_mod.StepTimePercentileTracker(
        window=TRACKER_WINDOW, clock=data_clock
    )
    telemetry.set_slo_engine(engine)
    health = telemetry.build_health_fn("training")

    burn_health = None
    for tick, event in enumerate(anat, 1):
        data_clock.t = float(event.get("monotonic", 0.0))
        engine.tracker.note_version(0, tick)
        for key, value in event.items():
            if not key.endswith("_ms") or key == "wall_ms":
                continue
            slot = cum.setdefault(key[: -len("_ms")], {"ms": 0.0})
            slot["ms"] += float(value)
        eval_clock.t = tick * TICK_SECS
        engine.evaluate({}, now=eval_clock.t)
        if burn_health is None and engine.active_violations():
            burn_health = health().get("slo")

    kinds = [t["kind"] for t in engine.transitions]
    if kinds != ["violation", "recovery"]:
        return _fail(
            f"expected exactly one violation then one recovery, got "
            f"{kinds} (objectives: "
            f"{[t['objective'] for t in engine.transitions]})"
        )
    if engine.transitions[0]["objective"] != "step_time_p95":
        return _fail(
            f"wrong objective fired: {engine.transitions[0]}"
        )
    if burn_health is None or burn_health.get("ok"):
        return _fail(
            f"/healthz slo block never flipped during the burn: "
            f"{burn_health!r}"
        )
    if not health().get("slo", {}).get("ok"):
        return _fail("/healthz slo block still degraded after recovery")
    if incidents.total_count != 1 or incidents.open_count != 0:
        return _fail(
            f"expected 1 closed incident, got total="
            f"{incidents.total_count} open={incidents.open_count}"
        )
    if len(armed) != 1:
        return _fail(
            f"profiler armed {len(armed)} times (expected exactly 1)"
        )

    records = read_incidents(watchdog_dir)
    if len(records) != 1:
        return _fail(
            f"{len(records)} incident artifacts under {watchdog_dir}"
        )
    record = records[0]
    if record.get("suspected_cause") != "input-bound":
        return _fail(
            "postmortem misattributed the injected regression: "
            f"{record.get('suspected_cause')!r} "
            f"({record.get('rationale')!r})"
        )
    if "host_fetch" not in record.get("rationale", ""):
        return _fail(
            f"rationale does not name the injected phase: "
            f"{record.get('rationale')!r}"
        )
    if record.get("objectives") != ["step_time_p95"]:
        return _fail(f"artifact objectives: {record.get('objectives')}")
    windows = [
        w.get("window_id") for w in record.get("profile_windows", [])
    ]
    if windows != armed:
        return _fail(
            f"artifact profile windows {windows} != armed {armed}"
        )
    if not any(
        entry.get("name") == "slo_violation"
        for entry in record.get("timeline", [])
    ):
        return _fail("artifact timeline lost the violation")

    # the scrape mirror: one firing on the elasticdl_slo_* families
    text = telemetry.registry.exposition()
    needle = 'elasticdl_slo_violations_total{objective="step_time_p95"} 1'
    if needle not in text:
        return _fail(f"/metrics missing {needle!r}")

    telemetry.events.flush()
    return {
        "watchdog_dir": watchdog_dir,
        "servicer": servicer,
        "window_id": armed[0],
        "violation": engine.transitions[0],
    }


def _profile_capture(workdir: str, servicer, window_id: int) -> int | dict:
    """Gate 4: the auto-armed window rides a heartbeat into a real
    StepProfiler capture (the PR-14 command path, replays absorbed)."""
    import glob

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.utils.profiling import (
        StepProfiler,
        apply_profile_command,
    )

    telemetry_dir = os.path.join(workdir, "capture_telemetry")
    worker_hooks.install(telemetry_dir)
    try:
        response = servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
        if not response.profile:
            return _fail(
                "heartbeat did not carry the auto-armed profile command"
            )
        profiler = StepProfiler("")
        if not apply_profile_command(
            profiler, response.profile, telemetry_dir=telemetry_dir,
            tag="w0",
        ):
            return _fail("apply_profile_command did not arm")
        replay = servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
        if apply_profile_command(
            profiler, replay.profile, telemetry_dir=telemetry_dir,
            tag="w0",
        ):
            return _fail("replayed profile command re-armed the window")

        step = jax.jit(lambda x: (x @ x.T).sum())
        value = jnp.ones((64, 64))
        for _ in range(SLO_CONFIG["profile_steps"] + 2):
            profiler.on_step()
            step(value).block_until_ready()
        profiler.stop()

        events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
        names = [e.get("event") for e in events]
        if "profile_window_open" not in names:
            return _fail("no profile_window_open event from the capture")
        closed = [
            e for e in events if e.get("event") == "profile_window_close"
        ]
        if not closed or closed[0].get("window_id") != window_id:
            return _fail(
                f"capture window id mismatch: {closed!r} vs incident's "
                f"{window_id}"
            )
        capture_root = os.path.join(
            telemetry_dir, "profile", f"window_{window_id}_w0"
        )
        artifacts = [
            p
            for p in glob.glob(
                os.path.join(capture_root, "**", "*"), recursive=True
            )
            if os.path.isfile(p)
        ]
        if not artifacts:
            return _fail(f"no capture artifacts under {capture_root}")
        return {"artifacts": len(artifacts)}
    finally:
        worker_hooks.uninstall()


def _report_verdict(watchdog_dir: str) -> int | dict:
    """Gate 5a: the machine-readable report over the watchdog's logs
    reaches the degraded-but-recovered verdict with the right cause."""
    from elasticdl_tpu.telemetry.report import (
        build_report,
        summarize_report,
    )

    summary = summarize_report(build_report(watchdog_dir))
    if summary["verdict"] != "degraded":
        return _fail(
            f"report verdict {summary['verdict']!r} (expected "
            f"'degraded'): {summary['reasons']}"
        )
    slo = summary["slo"]
    if slo["violations"] != 1 or slo["recoveries"] != 1 or slo["still_firing"]:
        return _fail(f"report slo summary wrong: {slo}")
    inc = summary["incidents"]
    if (
        inc["total"] != 1
        or inc["open"] != 0
        or inc["causes"] != {"input-bound": 1}
    ):
        return _fail(f"report incident summary wrong: {inc}")
    return summary


def _fleetsim_mute(workdir: str) -> int | dict:
    """Gate 5b: a silenced watchdog must FAIL the fleet gate (rc 1,
    slo_detection invariant tripped) — detection is falsifiable."""
    from elasticdl_tpu.fleetsim.runner import run_plan

    mute_dir = os.path.join(workdir, "fleet_mute")
    os.makedirs(mute_dir)
    logging.disable(logging.CRITICAL)  # netem chaos logs spam stdout
    try:
        result = run_plan(
            "fleet_mass_preemption",
            mute_dir,
            workers=48,
            num_tasks=120,
            seed=11,
            corrupt="mute_slo",
        )
    finally:
        logging.disable(logging.NOTSET)
    if result["rc"] != 1:
        return _fail(
            f"--corrupt mute_slo exited {result['rc']} (expected 1)"
        )
    failed = {
        i["name"]
        for i in result["invariants"]
        if i["status"] == "FAIL"
    }
    if "slo_detection" not in failed:
        return _fail(
            f"mute_slo tripped {sorted(failed)}, not slo_detection"
        )
    return {"failed": sorted(failed)}


def main() -> int:
    with tempfile.TemporaryDirectory() as workdir:
        anat = _train_with_regression(workdir)
        if isinstance(anat, int):
            return anat
        verdict = _watchdog_verdict(workdir, anat)
        if isinstance(verdict, int):
            return verdict
        captured = _profile_capture(
            workdir, verdict["servicer"], verdict["window_id"]
        )
        if isinstance(captured, int):
            return captured
        reported = _report_verdict(verdict["watchdog_dir"])
        if isinstance(reported, int):
            return reported
        muted = _fleetsim_mute(workdir)
        if isinstance(muted, int):
            return muted

    violation = verdict["violation"]
    print(
        "slo_smoke: OK ({} dispatches, step_time_p95 fired once at "
        "{:.0f}ms vs threshold {:.0f}ms then recovered | incident 1 "
        "input-bound, profile window {} with {} artifacts | report "
        "verdict degraded | mute_slo tripped {})".format(
            len(anat),
            violation["value"],
            violation["threshold"],
            verdict["window_id"],
            captured["artifacts"],
            ", ".join(muted["failed"]),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
