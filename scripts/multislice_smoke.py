#!/usr/bin/env python3
"""Tier-1 multislice smoke (wired into scripts/run_tier1.sh).

Runs a tiny 2-process lockstep mnist job on the CPU backend with a
FORCED 2-slice hybrid ICI/DCN layout (``--num_slices 2`` — each process
is one slice; devices carry no ``slice_index``, so the canonical
process->slice map drives ``slice_index_fn``) under the
``slice_loss_mid_epoch`` chaos plan with peer state replication ON and
``checkpoint_steps`` coarser than the replication cadence, then
requires slice-granular reform to have actually happened:

1. the chaos report's invariants all PASS — including
   ``cross_slice_replica_coverage`` (every replica push landed on a
   different slice than its source) and ``replication_no_lost_steps``
   (the shrunken world restored at exactly the last replicated step);
2. the span log contains a ``mesh_resize`` span whose slice count
   SHRANK (the dp axis contracted to the surviving slice set);
3. replication_smoke discipline extends across the resize: at least
   one ``replica_restore`` span and ZERO ``checkpoint_restore_state``
   spans — the slice loss recovered from the surviving slice's replica
   ring with no disk read on the critical path.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    import tempfile

    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_CHECKPOINT_RESTORE,
        SPAN_MESH_RESIZE,
        SPAN_REPLICA_RESTORE,
        SPANS_FILENAME,
        read_spans,
    )

    with tempfile.TemporaryDirectory() as workdir:
        report = run_chaos_job(
            ChaosJobConfig(
                plan=named_plan("slice_loss_mid_epoch", 2),
                workdir=os.path.join(workdir, "chaos"),
                num_records=256,
                num_epochs=2,
                num_workers=2,
                num_slices=2,
                # coarser than the per-boundary replication cadence: a
                # disk-only restore could NOT land at the step pushed
                # right before the slice died
                checkpoint_steps=4,
                replication=True,
                run_timeout_secs=300.0,
            )
        )
        failed = [
            i["name"]
            for i in report["invariants"]
            if i["status"] != "PASS"
        ]
        if not report["invariants_ok"] or failed:
            print(
                f"multislice_smoke: invariants failed: {failed} "
                f"(rc={report.get('rc')}, timed_out="
                f"{report.get('timed_out')})",
                file=sys.stderr,
            )
            return 1
        names = [i["name"] for i in report["invariants"]]
        for required in (
            "cross_slice_replica_coverage",
            "replication_no_lost_steps",
        ):
            if required not in names:
                print(
                    f"multislice_smoke: {required} invariant missing "
                    "from the report",
                    file=sys.stderr,
                )
                return 1
        spans = read_spans(
            os.path.join(workdir, "chaos", "telemetry", SPANS_FILENAME)
        )
        resizes = [
            s for s in spans if s.get("span") == SPAN_MESH_RESIZE
        ]
        shrunk = [
            s
            for s in resizes
            if (s.get("new_slices") or 0) < (s.get("old_slices") or 0)
        ]
        if not shrunk:
            print(
                "multislice_smoke: no shrinking mesh_resize span — the "
                f"slice loss did not resize the dp axis (resizes: "
                f"{resizes})",
                file=sys.stderr,
            )
            return 1
        restores = [
            s for s in spans if s.get("span") == SPAN_REPLICA_RESTORE
        ]
        disk_reads = [
            s for s in spans if s.get("span") == SPAN_CHECKPOINT_RESTORE
        ]
        if not restores:
            print(
                "multislice_smoke: no replica_restore span — the "
                "shrunken world did not restore from the surviving "
                "slice's replica ring",
                file=sys.stderr,
            )
            return 1
        if disk_reads:
            print(
                f"multislice_smoke: {len(disk_reads)} "
                "checkpoint_restore_state span(s) — a disk read leaked "
                "onto the slice-loss recovery path",
                file=sys.stderr,
            )
            return 1
        stats = report.get("multislice") or {}
    print(
        "multislice_smoke: OK (mesh {}p/{}s -> {}p/{}s; restored at "
        "step {} from peer RAM; cross-slice pushes {})".format(
            shrunk[0].get("old_world_size"),
            shrunk[0].get("old_slices"),
            shrunk[0].get("new_world_size"),
            shrunk[0].get("new_slices"),
            restores[0].get("step"),
            stats.get("replica_pushes_by_source_slice"),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
