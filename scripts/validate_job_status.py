"""Poll a submitted job's pods until the job succeeds or fails.

Port of ``/root/reference/scripts/validate_job_status.py:1-120`` to the
TPU build's pod topology: master + worker pods only (no PS pods), pods
discovered by the ``elasticdl-job-name`` label rather than fixed names
(elastic relaunches use fresh worker ids, so name guessing would miss
them).

Success: the master pod reaches phase ``Succeeded`` (our master exits
after the job; it does not idle for TensorBoard the way the reference
master does, reference master.py:217-230).
Failure: the master pod fails, or any labeled pod sits in ``Failed``
while the master is gone.

Exit code 0 on success, 1 on failure/timeout. Dumps the master log (and
failed worker logs) on failure.
"""

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--job_name", required=True)
    p.add_argument("--namespace", default="default")
    p.add_argument("--timeout_secs", type=int, default=600)
    p.add_argument("--poll_secs", type=float, default=3.0)
    args = p.parse_args(argv)

    from kubernetes import client as k8s_client
    from kubernetes import config as k8s_config

    k8s_config.load_kube_config()
    api = k8s_client.CoreV1Api()
    master_name = f"elasticdl-{args.job_name}-master"
    selector = f"elasticdl-job-name={args.job_name}"

    def master_phase():
        try:
            pod = api.read_namespaced_pod(
                namespace=args.namespace, name=master_name
            )
            return pod.status.phase
        except Exception:  # noqa: BLE001 — not found / transient API
            return ""

    def labeled_pods():
        return api.list_namespaced_pod(
            namespace=args.namespace, label_selector=selector
        ).items

    def dump_logs():
        for pod in labeled_pods():
            print(f"---- log {pod.metadata.name} ({pod.status.phase}) ----")
            try:
                print(
                    api.read_namespaced_pod_log(
                        namespace=args.namespace, name=pod.metadata.name,
                        tail_lines=200,
                    )
                )
            except Exception as e:  # noqa: BLE001
                print(f"(log unavailable: {e})")

    deadline = time.time() + args.timeout_secs
    while time.time() < deadline:
        phase = master_phase()
        if phase == "Succeeded":
            print(f"Job {args.job_name} succeeded.")
            return 0
        if phase == "Failed":
            print(f"Job {args.job_name} FAILED (master pod Failed).")
            dump_logs()
            return 1
        failed = [
            p.metadata.name
            for p in labeled_pods()
            if p.status.phase == "Failed"
        ]
        if failed and not phase:
            # workers failed and the master is gone: nothing will recover
            print(f"Job {args.job_name} FAILED (pods: {failed}).")
            dump_logs()
            return 1
        time.sleep(args.poll_secs)

    print(f"Timed out after {args.timeout_secs}s (master phase: "
          f"{master_phase() or 'missing'}).")
    dump_logs()
    return 1


if __name__ == "__main__":
    sys.exit(main())
