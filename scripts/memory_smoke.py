#!/usr/bin/env python3
"""Tier-1 memory-observability smoke (wired into scripts/run_tier1.sh).

Four gates over the memory ledger (telemetry/memory.py), end to end on
real runs:

1. **Training ledger** — a tiny LocalExecutor mnist job with telemetry
   must produce a ``memory`` section in ``telemetry.report``: the
   ``model_state`` component carries real bytes (> 0), every
   component's peak >= its current, and the host-RSS residual is under
   the documented absolute-bytes budget (the explicit ``unaccounted``
   line — allocators lie, so the ledger surfaces the residual instead
   of pretending sum-exactness).
2. **Serving hot swap under traffic** — an in-process replica serving
   the trained export is hammered by concurrent predict threads while
   the model hot-swaps: zero failed requests, and the ledger's
   ``serving_model`` PEAK shows the transient double residency (old +
   new leaves resident at once) that then releases (current settles
   back under the peak).
3. **/metrics** — heartbeat-shipped ledger snapshots render as
   ``elasticdl_memory_bytes{component=,kind=current|peak}`` gauges, a
   newer-stamped LOWER sample lowers the current series (last-writer-
   wins, not a ratchet) while the peak holds, and the family stays
   under the fleetsim cardinality cap even when a payload floods
   component names.
4. **On-demand profiler round trip** — ``request_profile`` on the real
   servicer rides a heartbeat response down, arms the worker-side
   ``StepProfiler`` through the same ``apply_profile_command`` path the
   workers run, and a short jitted loop produces a LOADABLE capture
   (trace artifacts on disk) plus ``profile_window_open``/
   ``profile_window_close`` events; a replayed command is absorbed.

The disabled-path cost (one global load + None check per sample site)
is machine-checked by elastic-lint's hot-path gate, which runs first in
run_tier1.sh.
"""

from __future__ import annotations

import glob
import os
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the double-residency gate: the swap peak must cover most of two
# resident copies (1.8x leaves slack for accounting noise)
DOUBLE_RESIDENCY_FACTOR = 1.8
# cardinality budget used for the /metrics gate
SERIES_BUDGET = 8


def _fail(message: str) -> int:
    print(f"memory_smoke: {message}", file=sys.stderr)
    return 1


def _train_window(workdir: str) -> tuple[dict, str] | int:
    """Gate 1: instrumented LocalExecutor run -> report memory section."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.telemetry import memory as memory_mod
    from elasticdl_tpu.telemetry import tracing, worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.telemetry.report import memory_section
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    train = synthetic.gen_mnist(
        os.path.join(workdir, "train"),
        num_records=512,
        num_shards=1,
        seed=11,
    )
    telemetry_dir = os.path.join(workdir, "telemetry")
    export_dir = os.path.join(workdir, "export")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "64",
            "--records_per_task",
            "128",
            "--num_epochs",
            "1",
            "--telemetry_dir",
            telemetry_dir,
            "--output",
            export_dir,
        ]
    )
    try:
        LocalExecutor(args).run()
    finally:
        worker_hooks.uninstall()
        tracing.uninstall()
        memory_mod.uninstall()

    events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
    section = memory_section(events)
    if not section:
        return _fail("telemetry.report emitted no memory section")
    components = section["components"]
    model = components.get("model_state")
    if not model or model["current_bytes"] <= 0:
        return _fail(
            f"model_state bytes not measured: {model!r} "
            f"(components: {sorted(components)})"
        )
    for name, slot in components.items():
        if slot["peak_bytes"] < slot["current_bytes"]:
            return _fail(
                f"component {name}: peak {slot['peak_bytes']} < "
                f"current {slot['current_bytes']}"
            )
    if section.get("host_rss_bytes") is None:
        return _fail("host RSS not read (/proc/self/status)")
    if section.get("unaccounted_over_budget"):
        return _fail(
            "unaccounted bytes over budget: "
            f"{section['unaccounted_bytes']} > "
            f"{section['unaccounted_budget_bytes']}"
        )
    share = section.get("unaccounted_share_of_rss")
    if share is None or not (0.0 <= share <= 1.0):
        return _fail(f"unaccounted share not computed: {share!r}")
    return section, export_dir


def _serving_window(workdir: str, export_dir: str) -> int | dict:
    """Gate 2: hot swap under a request hammer — double-residency peak
    observed, then released; zero failed requests."""
    import numpy as np

    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.serving.replica import ServingReplica
    from elasticdl_tpu.telemetry import memory as memory_mod
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.telemetry.report import serving_section
    from elasticdl_tpu.utils.export_utils import read_manifest

    telemetry_dir = os.path.join(workdir, "serving_telemetry")
    worker_hooks.install(telemetry_dir)
    # install AFTER worker_hooks so ledger samples emit memory_sample
    # events into this window's event log
    ledger = memory_mod.install_if_enabled(telemetry_dir)
    replica = ServingReplica(export_dir, canonical_rows=64)
    replica.start()
    try:
        rng = np.random.RandomState(5)

        def one_request(i: int):
            # the mnist zoo's wire schema: uint8 images under "image"
            feats = {
                "image": rng.randint(
                    0, 255, size=(1 + (i % 7), 28, 28), dtype=np.uint8
                )
            }
            return replica.servicer.predict(
                msg.PredictRequest(
                    request_id=f"r{i}",
                    features=msg.pack_array_tree(feats),
                    rows=feats["image"].shape[0],
                )
            )

        warm = one_request(0)
        if warm.error:
            return _fail(f"warmup request failed: {warm.error}")
        built = ledger.snapshot()["current"].get("serving_model", 0)
        if built <= 0:
            return _fail("serving_model bytes not measured after build")

        failures: list[str] = []
        stop = threading.Event()

        def hammer(tid: int):
            i = 0
            while not stop.is_set():
                response = one_request(tid * 10_000 + i)
                if response.error:
                    failures.append(response.error)
                i += 1

        threads = [
            threading.Thread(target=hammer, args=(t,), daemon=True)
            for t in range(4)
        ]
        for thread in threads:
            thread.start()
        # hot swap mid-traffic: same flats re-keyed to a newer version
        manifest = read_manifest(export_dir)
        flat_params = {}
        with np.load(os.path.join(export_dir, "params.npz")) as z:
            flat_params = {k: z[k] for k in z.files}
        accepted, version, reason = replica.engine.swap_state_dicts(
            flat_params, {}, version=int(manifest["model_version"]) + 1
        )
        if not accepted:
            return _fail(f"hot swap refused: {reason}")
        stop.set()
        for thread in threads:
            thread.join(timeout=10)
        if failures:
            return _fail(
                f"{len(failures)} requests failed under swap "
                f"(first: {failures[0]})"
            )
        snap = ledger.snapshot()
        peak = snap["peak"].get("serving_model", 0)
        current = snap["current"].get("serving_model", 0)
        if peak < int(DOUBLE_RESIDENCY_FACTOR * built):
            return _fail(
                f"swap double residency not observed: peak {peak} < "
                f"{DOUBLE_RESIDENCY_FACTOR} x built {built}"
            )
        if current >= peak:
            return _fail(
                f"swap residency never released: current {current} >= "
                f"peak {peak}"
            )
        events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
        swaps = [e for e in events if e.get("event") == "memory_sample"
                 and e.get("phase") == "model_swap"]
        if not swaps:
            return _fail("no model_swap phase-edge memory samples")
        section = serving_section(events)
        if not section or section["requests"] <= 0:
            return _fail("report serving section missing/empty")
        if not section["swaps"]:
            return _fail("report serving section lost the swap timeline")
        return {
            "built": built,
            "peak": peak,
            "current": current,
            "requests": section["requests"],
        }
    finally:
        replica.close()
        worker_hooks.uninstall()
        memory_mod.uninstall()


def _metrics_window() -> int | dict:
    """Gate 3: heartbeat -> /metrics mirror, release visible, series
    capped."""
    os.environ["ELASTICDL_TPU_WORKER_SERIES_MAX"] = str(SERIES_BUDGET)
    try:
        from elasticdl_tpu.master.servicer import MasterServicer
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.rpc import messages as msg
        from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

        dispatcher = TaskDispatcher(
            {"s": (0, 64)}, records_per_task=64, num_epochs=1
        )
        servicer = MasterServicer(64, dispatcher)
        telemetry = MasterTelemetry()
        telemetry.attach(dispatcher, servicer)

        def beat(at, current, peak):
            servicer.heartbeat(
                msg.HeartbeatRequest(
                    worker_id=1,
                    memory={"at": at, "current": current, "peak": peak},
                )
            )

        beat(1.0, {"model_state": 1000}, {"model_state": 1000})
        text = telemetry.registry.exposition()
        needle = (
            'elasticdl_memory_bytes{component="model_state",'
            'kind="current"} 1000'
        )
        if needle not in text:
            return _fail(f"/metrics missing {needle!r}")
        # a newer, LOWER sample must lower current and hold the peak
        beat(2.0, {"model_state": 250}, {"model_state": 1000})
        text = telemetry.registry.exposition()
        if (
            'component="model_state",kind="current"} 250' not in text
            or 'component="model_state",kind="peak"} 1000' not in text
        ):
            return _fail("release not visible on /metrics (or peak lost)")
        # cardinality: a flood of component names collapses into the cap
        flood = {f"c{i:03d}": i + 1 for i in range(64)}
        beat(3.0, flood, flood)
        text = telemetry.registry.exposition()
        series = [
            line
            for line in text.splitlines()
            if line.startswith("elasticdl_memory_bytes{")
        ]
        if len(series) > 2 * SERIES_BUDGET:
            return _fail(
                f"memory series cardinality {len(series)} exceeds "
                f"2 x budget {SERIES_BUDGET}"
            )
        if 'component="other"' not in text:
            return _fail("flooded components did not collapse to 'other'")
        return {"series": len(series), "servicer": servicer,
                "telemetry": telemetry}
    finally:
        os.environ.pop("ELASTICDL_TPU_WORKER_SERIES_MAX", None)


def _profile_window(workdir: str, servicer) -> int | dict:
    """Gate 4: request_profile -> heartbeat -> arm -> loadable capture +
    window events, replays absorbed."""
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.utils.profiling import (
        StepProfiler,
        apply_profile_command,
    )

    telemetry_dir = os.path.join(workdir, "profile_telemetry")
    worker_hooks.install(telemetry_dir)
    try:
        first = servicer.request_profile(
            msg.RequestProfileRequest(num_steps=3)
        )
        if not first.accepted or first.window_id <= 0:
            return _fail(f"request_profile refused: {first!r}")
        duplicate = servicer.request_profile(
            msg.RequestProfileRequest(num_steps=3)
        )
        if duplicate.window_id != first.window_id:
            return _fail(
                "duplicate request_profile opened a second window "
                f"({first.window_id} -> {duplicate.window_id})"
            )
        response = servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
        if not response.profile:
            return _fail("heartbeat response did not carry the command")
        profiler = StepProfiler("")
        if not apply_profile_command(
            profiler, response.profile, telemetry_dir=telemetry_dir,
            tag="w0",
        ):
            return _fail("apply_profile_command did not arm")
        # the replayed command on the NEXT beat is absorbed
        replay = servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
        if apply_profile_command(
            profiler, replay.profile, telemetry_dir=telemetry_dir, tag="w0"
        ):
            return _fail("replayed profile command re-armed the window")

        step = jax.jit(lambda x: (x @ x.T).sum())
        value = jnp.ones((64, 64))
        for _ in range(6):
            profiler.on_step()
            step(value).block_until_ready()
        profiler.stop()

        events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
        names = [e.get("event") for e in events]
        if "profile_window_open" not in names:
            return _fail("no profile_window_open event")
        if "profile_window_close" not in names:
            return _fail("no profile_window_close event")
        closed = next(
            e for e in events if e.get("event") == "profile_window_close"
        )
        if closed.get("window_id") != first.window_id:
            return _fail(
                f"close event window_id {closed.get('window_id')} != "
                f"{first.window_id}"
            )
        capture_root = os.path.join(
            telemetry_dir, "profile", f"window_{first.window_id}_w0"
        )
        artifacts = glob.glob(
            os.path.join(capture_root, "**", "*"), recursive=True
        )
        artifacts = [p for p in artifacts if os.path.isfile(p)]
        if not artifacts:
            return _fail(f"no capture artifacts under {capture_root}")
        return {"window_id": first.window_id, "artifacts": len(artifacts)}
    finally:
        worker_hooks.uninstall()


def main() -> int:
    with tempfile.TemporaryDirectory() as workdir:
        trained = _train_window(workdir)
        if isinstance(trained, int):
            return trained
        section, export_dir = trained
        served = _serving_window(workdir, export_dir)
        if isinstance(served, int):
            return served
        metrics = _metrics_window()
        if isinstance(metrics, int):
            return metrics
        profiled = _profile_window(workdir, metrics["servicer"])
        if isinstance(profiled, int):
            return profiled

    model_mb = section["components"]["model_state"]["current_bytes"] / 1e6
    print(
        "memory_smoke: OK (model_state {:.2f} MB over {} components, "
        "unaccounted {:.0f} MB under budget | swap: built {:.2f} MB "
        "peak {:.2f} MB released to {:.2f} MB over {} requests | "
        "/metrics {} series | profile window {} with {} artifacts)".format(
            model_mb,
            len(section["components"]),
            (section["unaccounted_bytes"] or 0) / 1e6,
            served["built"] / 1e6,
            served["peak"] / 1e6,
            served["current"] / 1e6,
            served["requests"],
            metrics["series"],
            profiled["window_id"],
            profiled["artifacts"],
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
