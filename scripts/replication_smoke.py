#!/usr/bin/env python3
"""Tier-1 replication smoke (wired into scripts/run_tier1.sh).

Runs a tiny 2-process lockstep mnist job on the CPU backend under the
``preempt_after_replication`` chaos plan with peer state replication ON
(``checkpoint_steps`` deliberately coarser than the replication cadence,
so disk restore alone could NOT land at the preempted step), then
requires the restore to have been served from peer RAM:

1. the chaos report's invariants all PASS (including
   ``replication_no_lost_steps``: the resumed generation restored at
   exactly the last replicated step);
2. the span log contains at least one ``replica_restore`` span in the
   post-reform generation;
3. the span log contains NO ``checkpoint_restore_state`` span — the
   reform critical path never touched a disk checkpoint.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    import tempfile

    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_CHECKPOINT_RESTORE,
        SPAN_REPLICA_RESTORE,
        SPANS_FILENAME,
        read_spans,
    )

    with tempfile.TemporaryDirectory() as workdir:
        report = run_chaos_job(
            ChaosJobConfig(
                plan=named_plan("preempt_after_replication", 2),
                workdir=os.path.join(workdir, "chaos"),
                num_records=256,
                num_epochs=2,
                num_workers=2,
                # coarser than the per-boundary replication cadence: a
                # disk-only restore would land at version 4, the replica
                # restore at the version pushed right before the kill
                checkpoint_steps=4,
                replication=True,
                run_timeout_secs=300.0,
            )
        )
        failed = [
            i["name"]
            for i in report["invariants"]
            if i["status"] != "PASS"
        ]
        if not report["invariants_ok"] or failed:
            print(
                f"replication_smoke: invariants failed: {failed} "
                f"(rc={report.get('rc')}, timed_out="
                f"{report.get('timed_out')})",
                file=sys.stderr,
            )
            return 1
        names = [i["name"] for i in report["invariants"]]
        if "replication_no_lost_steps" not in names:
            print(
                "replication_smoke: replication_no_lost_steps invariant "
                "missing from the report",
                file=sys.stderr,
            )
            return 1
        spans = read_spans(
            os.path.join(workdir, "chaos", "telemetry", SPANS_FILENAME)
        )
        restores = [
            s for s in spans if s.get("span") == SPAN_REPLICA_RESTORE
        ]
        disk_reads = [
            s for s in spans if s.get("span") == SPAN_CHECKPOINT_RESTORE
        ]
        if not restores:
            print(
                "replication_smoke: no replica_restore span — the "
                "re-formed world did not restore from peer RAM",
                file=sys.stderr,
            )
            return 1
        if disk_reads:
            print(
                f"replication_smoke: {len(disk_reads)} "
                "checkpoint_restore_state span(s) — a disk read leaked "
                "onto the reform critical path",
                file=sys.stderr,
            )
            return 1
        stats = report.get("replication", {})
    print(
        "replication_smoke: OK (restored at step "
        f"{restores[0].get('step')} from peer RAM; pushes per generation "
        f"{stats.get('pushes_by_generation')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
