#!/usr/bin/env python3
"""Tier-1 serving smoke (wired into scripts/run_tier1.sh).

The serving-plane contract, end to end through the REAL CLI
(``python -m elasticdl_tpu.serving.main``, frontend + 1 replica
subprocess over gRPC):

1. train a tiny MNIST job and export it; serve the export;
2. fire mixed-size CONCURRENT requests (1, 7, canonical, canonical+3
   rows): every response must be per-row IDENTICAL to the training
   trainer's direct forward, and every response's phase decomposition
   must sum exactly to its total;
3. compile-once: after one warmup request the replica's process-wide
   compile counter must stay FLAT across all the mixed traffic —
   arbitrary request sizes hit one pre-compiled XLA program;
4. hot swap: export a newer version, swap it in through the router
   while a hammer thread keeps requests in flight — ZERO failed
   requests, the served version advances, post-swap outputs match the
   new weights, and the compile counter is STILL flat;
5. the telemetry dir (env-forwarded to the replica like a worker)
   carries ``serving_request`` events with sum-exact phases and one
   ``model_swap`` event.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

CANONICAL = 8


def _fail(message: str) -> int:
    print(f"serving_smoke: {message}", file=sys.stderr)
    return 1


def main() -> int:  # noqa: PLR0915 — one linear smoke scenario
    import numpy as np

    import jax
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.rpc.deadline import DeadlinePolicy
    from elasticdl_tpu.serving.replica import ServingClient
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.parallel.distributed import trim_pad

    workdir = tempfile.mkdtemp(prefix="edl_serving_smoke_")
    train_dir = synthetic.gen_mnist(
        os.path.join(workdir, "train"), num_records=32, num_shards=1, seed=1
    )
    export_v1 = os.path.join(workdir, "export_v1")
    telemetry_dir = os.path.join(workdir, "telemetry")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--minibatch_size",
            str(CANONICAL),
            "--records_per_task",
            "32",
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
            "--output",
            export_v1,
        ]
    )
    executor = LocalExecutor(args)
    executor.run()
    v1 = int(executor.state.step)

    # a NEWER version to hot-swap to (perturbed weights, advanced step)
    export_v2 = os.path.join(workdir, "export_v2")
    state_v2 = executor.state.replace(
        params=jax.tree_util.tree_map(
            lambda x: x * 1.5 + 0.01, executor.state.params
        ),
        step=executor.state.step + 5,
    )
    export_model(export_v2, state_v2, None, args)
    v2 = v1 + 5

    # ---- serve export_v1 through the real CLI -------------------------------
    addr_file = os.path.join(workdir, "serving.addr")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.serving.main",
            "--model_dir",
            export_v1,
            "--num_replicas",
            "1",
            "--port",
            "0",
            "--addr_file",
            addr_file,
            "--minibatch_size",
            str(CANONICAL),
            "--max_wait_ms",
            "2",
            "--telemetry_dir",
            telemetry_dir,
            "--metrics_port",
            "-1",
        ],
        env=dict(os.environ),
    )
    client = None
    try:
        deadline = time.monotonic() + 120
        addr = ""
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return _fail(f"serving CLI exited rc={proc.returncode}")
            try:
                with open(addr_file, encoding="utf-8") as f:
                    addr = f.read().strip()
                if addr:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        if not addr:
            return _fail("frontend never published its address")
        client = ServingClient(addr, deadlines=DeadlinePolicy.from_secs(30))

        rng = np.random.RandomState(0)

        def feats(n: int) -> dict:
            return {"image": rng.rand(n, 28, 28, 1).astype(np.float32)}

        def predict(request_id: str, features: dict):
            return client.predict(
                msg.PredictRequest(
                    request_id=request_id,
                    features=msg.pack_array_tree(features),
                )
            )

        # warmup: the first dispatch pays the one compile
        warm = predict("warmup", feats(CANONICAL))
        if warm.error:
            return _fail(f"warmup failed: {warm.error}")
        status0 = client.serving_status()
        if status0.compile_count <= 0:
            return _fail("replica reports zero compiles after warmup")

        # mixed sizes, concurrently
        from concurrent.futures import ThreadPoolExecutor

        sizes = [1, 7, CANONICAL, CANONICAL + 3]
        inputs = [feats(n) for n in sizes]
        with ThreadPoolExecutor(len(sizes)) as pool:
            futures = [
                pool.submit(predict, f"mixed-{i}", x)
                for i, x in enumerate(inputs)
            ]
            responses = [f.result() for f in futures]
        for n, x, response in zip(sizes, inputs, responses):
            if response.error:
                return _fail(f"{n}-row request failed: {response.error}")
            out = np.asarray(msg.unpack_array_tree(response.outputs))
            if out.shape[0] != n:
                return _fail(f"{n}-row request got {out.shape[0]} rows back")
            # per-row parity vs the training trainer's direct forward
            # (chunked to the canonical shape, exactly like the batcher)
            chunks = []
            for lo in range(0, n, CANONICAL):
                hi = min(n, lo + CANONICAL)
                part = {k: v[lo:hi] for k, v in x.items()}
                chunks.append(
                    trim_pad(
                        jax.device_get(
                            executor.trainer.predict_step(
                                executor.trainer.place_canonical(
                                    part, CANONICAL
                                )
                            )
                        ),
                        hi - lo,
                    )
                )
            direct = np.concatenate(chunks, axis=0)
            if not np.allclose(direct, out, atol=1e-5):
                return _fail(f"{n}-row outputs diverge from direct forward")
            # sum-exact per-request anatomy
            phases = dict(response.phases)
            total = phases.pop("total_ms", None)
            if total is None or abs(sum(phases.values()) - total) > 1e-3:
                return _fail(
                    f"{n}-row anatomy not sum-exact: {response.phases}"
                )
        status1 = client.serving_status()
        if status1.compile_count != status0.compile_count:
            return _fail(
                "RECOMPILE under mixed sizes: compile count "
                f"{status0.compile_count} -> {status1.compile_count}"
            )
        if status1.model_version != v1:
            return _fail(
                f"serving version {status1.model_version}, expected {v1}"
            )

        # ---- hot swap under in-flight traffic -------------------------------
        stop = threading.Event()
        failures: list[str] = []
        hammered = [0]

        def hammer():
            i = 0
            while not stop.is_set():
                response = predict(f"hammer-{i}", feats(3))
                if response.error:
                    failures.append(response.error)
                hammered[0] += 1
                i += 1

        thread = threading.Thread(target=hammer, daemon=True)
        thread.start()
        time.sleep(0.3)
        swap = client.swap_model(msg.SwapModelRequest(model_dir=export_v2))
        time.sleep(0.3)
        stop.set()
        thread.join(timeout=10)
        if not swap.accepted or swap.model_version != v2:
            return _fail(
                f"swap not accepted (accepted={swap.accepted}, "
                f"version={swap.model_version}, reason={swap.reason!r})"
            )
        if failures:
            return _fail(
                f"{len(failures)}/{hammered[0]} in-flight requests failed "
                f"across the swap (first: {failures[0]})"
            )
        if hammered[0] == 0:
            return _fail("hammer thread never got a request through")

        # post-swap outputs match the NEW weights, compile still flat
        check = feats(5)
        response = predict("post-swap", check)
        if response.error or response.model_version != v2:
            return _fail(
                f"post-swap predict failed (error={response.error!r}, "
                f"version={response.model_version})"
            )
        # same forward path as the pre-swap parity (device_parse and
        # all): point the training trainer at the v2 state
        executor.trainer.state = state_v2
        direct_v2 = trim_pad(
            jax.device_get(
                executor.trainer.predict_step(
                    executor.trainer.place_canonical(check, CANONICAL)
                )
            ),
            5,
        )
        out = np.asarray(msg.unpack_array_tree(response.outputs))
        if not np.allclose(direct_v2, out, atol=1e-5):
            return _fail("post-swap outputs do not match the new weights")
        status2 = client.serving_status()
        if status2.compile_count != status0.compile_count:
            return _fail(
                "RECOMPILE across hot swap: compile count "
                f"{status0.compile_count} -> {status2.compile_count}"
            )

        # ---- telemetry: serving events landed -------------------------------
        from elasticdl_tpu.telemetry.events import (
            EVENT_MODEL_SWAP,
            EVENT_SERVING_REQUEST,
            read_events,
        )

        events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
        n_requests = sum(
            1 for e in events if e.get("event") == EVENT_SERVING_REQUEST
        )
        n_swaps = sum(
            1 for e in events if e.get("event") == EVENT_MODEL_SWAP
        )
        if n_requests < len(sizes) + 2:
            return _fail(
                f"only {n_requests} serving_request events in telemetry"
            )
        if n_swaps != 1:
            return _fail(f"{n_swaps} model_swap events, expected 1")

        print(
            "serving_smoke: OK "
            f"(mixed sizes {sizes} all exact, compile count flat at "
            f"{status0.compile_count} across traffic AND swap "
            f"{v1}->{v2}, {hammered[0]} in-flight requests with 0 "
            f"failures, {n_requests} serving_request events)"
        )
        return 0
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
