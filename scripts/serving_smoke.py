#!/usr/bin/env python3
"""Tier-1 serving smoke (wired into scripts/run_tier1.sh).

The serving-plane contract, end to end through the REAL CLI
(``python -m elasticdl_tpu.serving.main``, frontend + 1 replica
subprocess over gRPC):

1. train a tiny MNIST job and export it; serve the export;
2. fire mixed-size CONCURRENT requests (1, 7, canonical, canonical+3
   rows), each under a client-side trace: every response must be
   per-row IDENTICAL to the training trainer's direct forward, and
   every response's phase decomposition must sum exactly to its total;
3. compile-once: after one warmup request the replica's process-wide
   compile counter must stay FLAT across all the mixed traffic —
   arbitrary request sizes hit one pre-compiled XLA program;
4. hot swap: export a newer version, swap it in through the router
   while a hammer thread keeps requests in flight — ZERO failed
   requests, the served version advances, post-swap outputs match the
   new weights, and the compile counter is STILL flat;
5. SLO watchdog: a deliberate queue flood trips the router-side
   ``serving_queue_wait`` objective EXACTLY once (slo_violation +
   incident_open), light follow-up traffic recovers it (slo_recovered
   + incident_close), and the incident postmortem classifies the cause
   as queue-bound naming the offending replica;  /healthz carries the
   per-replica probe ages and the slo block flip, /metrics the
   ``elasticdl_serving_replica_*`` fan-in families;
6. the telemetry dir (env-forwarded to the replica like a worker)
   carries ``serving_request`` events with sum-exact phases and one
   ``model_swap`` event — and after a graceful shutdown, ONE trace per
   mixed request spanning all three processes (client root, router
   route, replica queue/engine) with the batched dispatch group LINKED
   to its member traces; the analyzer's serving critical path and the
   Chrome export both read it back.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

CANONICAL = 8

# one objective, tuned so only a SUSTAINED flood fires it: fire needs
# min_evals consecutive bad probe ticks (fire_share 1.0) inside the
# fast window, so the smoke's short bursts (mixed phase, swap hammer)
# can never produce 3-in-4s all-bad; the flood holds the queue deep for
# seconds and always does
SLO_CONFIG = json.dumps(
    {
        "objectives": [
            {
                "name": "serving_queue_wait",
                "signal": "queue_wait_share",
                "comparator": "above",
                "threshold": 0.6,
                "windows": {
                    "fast_secs": 4.0,
                    "slow_secs": 8.0,
                    "min_evals": 3,
                },
            }
        ]
    }
)


def _fail(message: str) -> int:
    print(f"serving_smoke: {message}", file=sys.stderr)
    return 1


def _http_get(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.read().decode("utf-8")


def main() -> int:  # noqa: PLR0915 — one linear smoke scenario
    import numpy as np

    import jax
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.rpc.deadline import DeadlinePolicy
    from elasticdl_tpu.serving.replica import ServingClient
    from elasticdl_tpu.telemetry import tracing
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.parallel.distributed import trim_pad

    workdir = tempfile.mkdtemp(prefix="edl_serving_smoke_")
    train_dir = synthetic.gen_mnist(
        os.path.join(workdir, "train"), num_records=32, num_shards=1, seed=1
    )
    export_v1 = os.path.join(workdir, "export_v1")
    telemetry_dir = os.path.join(workdir, "telemetry")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--minibatch_size",
            str(CANONICAL),
            "--records_per_task",
            "32",
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
            "--output",
            export_v1,
        ]
    )
    executor = LocalExecutor(args)
    executor.run()
    v1 = int(executor.state.step)

    # a NEWER version to hot-swap to (perturbed weights, advanced step)
    export_v2 = os.path.join(workdir, "export_v2")
    state_v2 = executor.state.replace(
        params=jax.tree_util.tree_map(
            lambda x: x * 1.5 + 0.01, executor.state.params
        ),
        step=executor.state.step + 5,
    )
    export_model(export_v2, state_v2, None, args)
    v2 = v1 + 5

    # the smoke process IS the serving client: its root spans land in
    # the same spans.jsonl the router/replica write, so one request
    # reads back as one trace across three processes
    tracing.install(telemetry_dir, role="client")

    # ---- serve export_v1 through the real CLI -------------------------------
    addr_file = os.path.join(workdir, "serving.addr")
    metrics_addr_file = os.path.join(workdir, "metrics.addr")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.serving.main",
            "--model_dir",
            export_v1,
            "--num_replicas",
            "1",
            "--port",
            "0",
            "--addr_file",
            addr_file,
            "--minibatch_size",
            str(CANONICAL),
            "--max_wait_ms",
            "2",
            "--max_queue_rows",
            "4096",
            "--telemetry_dir",
            telemetry_dir,
            "--metrics_port",
            "0",
            "--metrics_addr_file",
            metrics_addr_file,
            "--slo_config",
            SLO_CONFIG,
        ],
        env=dict(os.environ),
    )
    client = None
    try:
        deadline = time.monotonic() + 120
        addr = metrics_addr = ""
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return _fail(f"serving CLI exited rc={proc.returncode}")
            for path, have in ((addr_file, addr), (metrics_addr_file, metrics_addr)):
                if have:
                    continue
                try:
                    with open(path, encoding="utf-8") as f:
                        text = f.read().strip()
                    if path == addr_file:
                        addr = text
                    else:
                        metrics_addr = text
                except OSError:
                    pass
            if addr and metrics_addr:
                break
            time.sleep(0.1)
        if not addr:
            return _fail("frontend never published its address")
        if not metrics_addr:
            return _fail("frontend never published its /metrics address")
        client = ServingClient(addr, deadlines=DeadlinePolicy.from_secs(30))

        rng = np.random.RandomState(0)

        def feats(n: int) -> dict:
            return {"image": rng.rand(n, 28, 28, 1).astype(np.float32)}

        def predict(request_id: str, features: dict, traced: bool = False):
            trace = {}
            span = None
            if traced:
                span = tracing.get_tracer().start_span(
                    tracing.SPAN_PREDICT_REQUEST, request_id=request_id
                )
                trace = span.context
            try:
                return client.predict(
                    msg.PredictRequest(
                        request_id=request_id,
                        features=msg.pack_array_tree(features),
                        trace=trace,
                    )
                )
            finally:
                if span is not None:
                    span.end()

        # warmup: the first dispatch pays the one compile
        warm = predict("warmup", feats(CANONICAL))
        if warm.error:
            return _fail(f"warmup failed: {warm.error}")
        status0 = client.serving_status()
        if status0.compile_count <= 0:
            return _fail("replica reports zero compiles after warmup")

        # mixed sizes, concurrently, each under its own client trace
        from concurrent.futures import ThreadPoolExecutor

        sizes = [1, 7, CANONICAL, CANONICAL + 3]
        inputs = [feats(n) for n in sizes]
        with ThreadPoolExecutor(len(sizes)) as pool:
            futures = [
                pool.submit(predict, f"mixed-{i}", x, True)
                for i, x in enumerate(inputs)
            ]
            responses = [f.result() for f in futures]
        for n, x, response in zip(sizes, inputs, responses):
            if response.error:
                return _fail(f"{n}-row request failed: {response.error}")
            out = np.asarray(msg.unpack_array_tree(response.outputs))
            if out.shape[0] != n:
                return _fail(f"{n}-row request got {out.shape[0]} rows back")
            # per-row parity vs the training trainer's direct forward
            # (chunked to the canonical shape, exactly like the batcher)
            chunks = []
            for lo in range(0, n, CANONICAL):
                hi = min(n, lo + CANONICAL)
                part = {k: v[lo:hi] for k, v in x.items()}
                chunks.append(
                    trim_pad(
                        jax.device_get(
                            executor.trainer.predict_step(
                                executor.trainer.place_canonical(
                                    part, CANONICAL
                                )
                            )
                        ),
                        hi - lo,
                    )
                )
            direct = np.concatenate(chunks, axis=0)
            if not np.allclose(direct, out, atol=1e-5):
                return _fail(f"{n}-row outputs diverge from direct forward")
            # sum-exact per-request anatomy
            phases = dict(response.phases)
            total = phases.pop("total_ms", None)
            if total is None or abs(sum(phases.values()) - total) > 1e-3:
                return _fail(
                    f"{n}-row anatomy not sum-exact: {response.phases}"
                )
        status1 = client.serving_status()
        if status1.compile_count != status0.compile_count:
            return _fail(
                "RECOMPILE under mixed sizes: compile count "
                f"{status0.compile_count} -> {status1.compile_count}"
            )
        if status1.model_version != v1:
            return _fail(
                f"serving version {status1.model_version}, expected {v1}"
            )

        # ---- hot swap under in-flight traffic -------------------------------
        stop = threading.Event()
        failures: list[str] = []
        hammered = [0]

        def hammer():
            i = 0
            while not stop.is_set():
                response = predict(f"hammer-{i}", feats(3))
                if response.error:
                    failures.append(response.error)
                hammered[0] += 1
                i += 1

        thread = threading.Thread(target=hammer, daemon=True)
        thread.start()
        time.sleep(0.3)
        swap = client.swap_model(msg.SwapModelRequest(model_dir=export_v2))
        time.sleep(0.3)
        stop.set()
        thread.join(timeout=10)
        if not swap.accepted or swap.model_version != v2:
            return _fail(
                f"swap not accepted (accepted={swap.accepted}, "
                f"version={swap.model_version}, reason={swap.reason!r})"
            )
        if failures:
            return _fail(
                f"{len(failures)}/{hammered[0]} in-flight requests failed "
                f"across the swap (first: {failures[0]})"
            )
        if hammered[0] == 0:
            return _fail("hammer thread never got a request through")

        # post-swap outputs match the NEW weights, compile still flat
        check = feats(5)
        response = predict("post-swap", check)
        if response.error or response.model_version != v2:
            return _fail(
                f"post-swap predict failed (error={response.error!r}, "
                f"version={response.model_version})"
            )
        # same forward path as the pre-swap parity (device_parse and
        # all): point the training trainer at the v2 state
        executor.trainer.state = state_v2
        direct_v2 = trim_pad(
            jax.device_get(
                executor.trainer.predict_step(
                    executor.trainer.place_canonical(check, CANONICAL)
                )
            ),
            5,
        )
        out = np.asarray(msg.unpack_array_tree(response.outputs))
        if not np.allclose(direct_v2, out, atol=1e-5):
            return _fail("post-swap outputs do not match the new weights")
        status2 = client.serving_status()
        if status2.compile_count != status0.compile_count:
            return _fail(
                "RECOMPILE across hot swap: compile count "
                f"{status0.compile_count} -> {status2.compile_count}"
            )

        # ---- /healthz + /metrics: the fan-in is scrapeable ------------------
        health = json.loads(_http_get(metrics_addr, "/healthz"))
        replica0 = (health.get("replicas") or {}).get("0")
        if not replica0 or "last_probe_age_secs" not in replica0:
            return _fail(f"/healthz missing per-replica probe age: {health}")
        if "outstanding" not in replica0 or "evict_in_secs" not in replica0:
            return _fail(f"/healthz replica block incomplete: {replica0}")
        slo_block = health.get("slo")
        if not slo_block or not slo_block.get("ok"):
            return _fail(f"/healthz slo block not healthy pre-flood: {slo_block}")
        metrics_text = _http_get(metrics_addr, "/metrics")
        for needle in (
            'elasticdl_serving_replica_queue_rows{replica="0"}',
            'elasticdl_serving_replica_probe_age_secs{replica="0"}',
            "elasticdl_serving_replica_phase_ms_total",
        ):
            if needle not in metrics_text:
                return _fail(f"/metrics missing {needle!r}")

        # ---- SLO watchdog: flood -> fire once -> recover ---------------------
        flood_stop = threading.Event()

        def flood():
            i = 0
            while not flood_stop.is_set():
                r = predict(f"flood-{i}", feats(48))
                if r.error:
                    failures.append(r.error)
                i += 1

        flood_threads = [
            threading.Thread(target=flood, daemon=True) for _ in range(6)
        ]
        for t in flood_threads:
            t.start()
        fired_block = None
        fire_deadline = time.monotonic() + 45
        while time.monotonic() < fire_deadline:
            block = json.loads(_http_get(metrics_addr, "/healthz")).get("slo")
            if block and not block.get("ok"):
                fired_block = block
                break
            time.sleep(0.3)
        flood_stop.set()
        for t in flood_threads:
            t.join(timeout=15)
        if fired_block is None:
            return _fail("queue flood never tripped the serving_queue_wait SLO")
        if failures:
            return _fail(
                f"{len(failures)} flood requests failed "
                f"(first: {failures[0]})"
            )

        # recovery needs HEALTHY traffic: the watchdog's signals are
        # per-tick deltas, so an idle fleet is dormant and the latched
        # objective would never clear — light sequential canonical
        # requests give it all-good fast-window samples
        recovered = False
        recover_deadline = time.monotonic() + 45
        i = 0
        while time.monotonic() < recover_deadline:
            predict(f"recover-{i}", feats(CANONICAL))
            i += 1
            block = json.loads(_http_get(metrics_addr, "/healthz")).get("slo")
            if block and block.get("ok") and not block.get("incidents_open"):
                recovered = True
                break
            time.sleep(0.25)
        if not recovered:
            return _fail("slo block never recovered after the flood stopped")

        # exactly-once transition discipline, straight from the event log
        from elasticdl_tpu.telemetry.events import (
            EVENT_INCIDENT_CLOSE,
            EVENT_INCIDENT_OPEN,
            EVENT_MODEL_SWAP,
            EVENT_SERVING_REQUEST,
            EVENT_SLO_RECOVERED,
            EVENT_SLO_VIOLATION,
            read_events,
        )

        events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
        counts = {
            name: sum(1 for e in events if e.get("event") == name)
            for name in (
                EVENT_SLO_VIOLATION,
                EVENT_SLO_RECOVERED,
                EVENT_INCIDENT_OPEN,
                EVENT_INCIDENT_CLOSE,
            )
        }
        if any(n != 1 for n in counts.values()):
            return _fail(f"SLO transitions not exactly-once: {counts}")

        # the postmortem: queue-bound, naming the flooded replica
        from elasticdl_tpu.telemetry.incident import read_incidents

        records = read_incidents(telemetry_dir)
        if len(records) != 1:
            return _fail(f"{len(records)} incident artifacts, expected 1")
        record = records[0]
        if record.get("suspected_cause") not in ("queue-bound", "compute-bound"):
            return _fail(
                f"incident cause {record.get('suspected_cause')!r} "
                f"({record.get('rationale')!r})"
            )
        if record.get("suspected_cause") != "queue-bound":
            return _fail(
                "flood misclassified (queue flood must read queue-bound): "
                f"{record.get('rationale')!r}"
            )
        if not any(
            v.get("replica_id") == 0 for v in record.get("violations", [])
        ):
            return _fail(
                f"incident does not name replica 0: {record.get('violations')}"
            )
        if "replica 0" not in record.get("rationale", ""):
            return _fail(
                f"rationale does not name the replica: "
                f"{record.get('rationale')!r}"
            )

        # ---- telemetry: serving events landed -------------------------------
        n_requests = sum(
            1 for e in events if e.get("event") == EVENT_SERVING_REQUEST
        )
        n_swaps = sum(
            1 for e in events if e.get("event") == EVENT_MODEL_SWAP
        )
        if n_requests < len(sizes) + 2:
            return _fail(
                f"only {n_requests} serving_request events in telemetry"
            )
        if n_swaps != 1:
            return _fail(f"{n_swaps} model_swap events, expected 1")

        # ---- graceful shutdown, then the cross-process traces ----------------
        client.close()
        client = None
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            return _fail("frontend did not exit on SIGTERM")
        tracing.flush()

        spans = tracing.read_spans(
            os.path.join(telemetry_dir, tracing.SPANS_FILENAME)
        )
        roots = {
            s.get("request_id"): s
            for s in spans
            if s.get("span") == tracing.SPAN_PREDICT_REQUEST
        }
        for i in range(len(sizes)):
            root = roots.get(f"mixed-{i}")
            if root is None:
                return _fail(f"no predict_request root span for mixed-{i}")
            tid = root["trace_id"]
            members = [s for s in spans if s.get("trace_id") == tid]
            names = {s.get("span") for s in members}
            roles = {s.get("role") for s in members}
            if not {"predict_request", "route", "queue", "engine"} <= names:
                return _fail(
                    f"trace {tid} (mixed-{i}) incomplete: spans {sorted(names)}"
                )
            if not {"client", "router", "replica"} <= roles:
                return _fail(
                    f"trace {tid} (mixed-{i}) does not span all three "
                    f"processes: roles {sorted(roles)}"
                )

        traced_ids = {roots[f"mixed-{i}"]["trace_id"] for i in range(len(sizes))}

        def _link_tids(span: dict) -> set:
            out = set()
            for link in span.get("links") or []:
                out.add(link.get("trace_id") if isinstance(link, dict) else link)
            return out

        linked = set()
        for span in spans:
            if span.get("span") == tracing.SPAN_SERVING_DISPATCH:
                linked |= _link_tids(span) & traced_ids
        if not linked:
            return _fail(
                "no serving_dispatch span links back to a traced request"
            )

        # the analyzer reads the same story back: a serving critical
        # path with a queue-vs-compute split that sums to request wall
        from elasticdl_tpu.telemetry.trace import (
            analyze_telemetry_dir,
            build_chrome_trace,
        )

        report = analyze_telemetry_dir(telemetry_dir)
        serving = report.get("serving")
        if not serving or serving["requests"] < len(sizes):
            return _fail(f"analyzer serving section missing/short: {serving}")
        # the attribution sweep's invariant: phases (including honest
        # "unattributed" for client-side stub/GIL time outside the
        # router/replica spans) sum EXACTLY to the measured request wall
        phase_sum = sum(serving["phases_secs"].values())
        if abs(phase_sum - serving["wall_secs_total"]) > 1e-3:
            return _fail(
                f"serving critical path not sum-exact: phases total "
                f"{phase_sum} vs wall {serving['wall_secs_total']}"
            )
        if serving["coverage"] is None or serving["coverage"] < 0.6:
            return _fail(
                f"serving critical path coverage {serving['coverage']} "
                f"(phases: {serving['phases_secs']})"
            )
        for phase in ("queue_wait", "compute"):
            if serving["phases_secs"].get(phase, 0.0) <= 0.0:
                return _fail(
                    f"serving critical path lost {phase!r}: "
                    f"{serving['phases_secs']}"
                )
        if serving["linked_dispatch_groups"] < 1:
            return _fail("analyzer saw no linked dispatch groups")

        chrome = build_chrome_trace(telemetry_dir)
        json.dumps(chrome)  # must be valid Chrome JSON
        track_names = {
            e.get("args", {}).get("name")
            for e in chrome.get("traceEvents", [])
            if e.get("name") == "process_name"
        }
        if not {"client", "router", "replica 0"} <= track_names:
            return _fail(
                f"Chrome export missing serving tracks: {sorted(track_names)}"
            )

        print(
            "serving_smoke: OK "
            f"(mixed sizes {sizes} all exact+traced, compile count flat at "
            f"{status0.compile_count} across traffic AND swap "
            f"{v1}->{v2}, {hammered[0]} in-flight requests with 0 "
            f"failures, SLO fired/recovered exactly once (queue-bound, "
            f"replica 0), {n_requests} serving_request events, "
            f"{serving['requests']} traced requests at coverage "
            f"{serving['coverage']})"
        )
        return 0
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
