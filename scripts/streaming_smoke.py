#!/usr/bin/env python3
"""Tier-1 streaming smoke (wired into scripts/run_tier1.sh).

The streaming subsystem's contract, end to end on CPU:

1. PREEMPT UNDER LOAD: an unbounded-source (bounded-prefix) streaming
   job — watermark-lease dispatch, NO epochs, NO checkpoints — survives
   a mid-stream SIGKILL of one worker with replication on: the leased
   windows requeue, the re-formed world restores from peer RAM at the
   replicated watermark (``replication_no_lost_steps``), accounting
   stays exactly-once, and ``lag = source_watermark -
   trained_watermark`` stays bounded (``bounded_lag``) with the stream
   fully drained at exit;
2. FALSIFIABILITY: the ``drop_stream_window`` corruption (a leased
   window silently lost, never requeued) MUST trip ``bounded_lag`` —
   the trained watermark can never cross the hole, so a green
   invariant that cannot fail is worthless;
3. LIVE PUSH UNDER HAMMER: a LIVE streaming job's ReplicaStore commits
   fan into a REAL serving CLI (frontend + 1 replica subprocess over
   gRPC) via ``--live_push_addr`` while hammer threads keep predict
   requests in flight — ZERO failed in-flight requests, the served
   version advances past the boot export with the replica's compile
   counter FLAT (the inline-payload swap reuses the compiled program),
   and ``telemetry.report`` renders the freshness ledger: one row per
   push with trained-watermark-at-swap vs source watermark (staleness).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# one 64-record window at batch 32 is 2 steps; 256 records = 4 windows
# = 8 steps across the fleet.  rate 64/s closes the bounded prefix in
# ~3s of wall clock (well inside lockstep startup), initial 64 gives
# the dispatcher a leasable backlog at t0.
STREAM_TOTAL = 256
STREAM_RATE = 64.0
STREAM_INITIAL = 64
SERVE_BATCH = 8


def _fail(message: str) -> int:
    print(f"streaming_smoke: {message}", file=sys.stderr)
    return 1


def _inv(report: dict, name: str) -> dict | None:
    for inv in report.get("invariants", []):
        if inv.get("name") == name:
            return inv
    return None


def _stream_config(workdir: str, plan, **overrides):
    from elasticdl_tpu.chaos.harness import ChaosJobConfig

    kwargs = dict(
        plan=plan,
        workdir=workdir,
        num_workers=2,
        streaming=True,
        stream_total=STREAM_TOTAL,
        stream_rate=STREAM_RATE,
        stream_initial=STREAM_INITIAL,
        replication=True,
        run_timeout_secs=240.0,
    )
    kwargs.update(overrides)
    return ChaosJobConfig(**kwargs)


def main() -> int:  # noqa: PLR0915 — one linear smoke scenario
    import numpy as np

    from elasticdl_tpu.chaos.harness import run_chaos_job
    from elasticdl_tpu.chaos.plan import resolve_plan

    root = tempfile.mkdtemp(prefix="edl_streaming_smoke_")

    # ---- stage 1: preempt under load --------------------------------------
    report = run_chaos_job(
        _stream_config(
            os.path.join(root, "preempt"),
            resolve_plan("streaming_preempt_under_load", 2),
        )
    )
    if report["timed_out"]:
        return _fail("preempt-under-load run timed out")
    if report["rc"] != 0 or not report["records_ok"]:
        return _fail(
            f"preempt-under-load run not green (rc={report['rc']}, "
            f"records_ok={report['records_ok']})"
        )
    if not report["invariants_ok"]:
        failed = [
            i["name"]
            for i in report["invariants"]
            if i["status"] == "FAIL"
        ]
        return _fail(f"preempt-under-load invariants failed: {failed}")
    for name in ("bounded_lag", "replication_no_lost_steps", "exactly_once"):
        inv = _inv(report, name)
        if inv is None or inv["status"] != "PASS":
            return _fail(f"invariant {name} did not PASS: {inv}")
    final = (report.get("streaming") or {}).get("final") or {}
    if final.get("trained_watermark") != STREAM_TOTAL or not final.get(
        "closed"
    ):
        return _fail(f"stream not drained at exit: {final}")
    lag_limit = _inv(report, "bounded_lag").get("lag_limit_records")
    print(
        "streaming_smoke: preempt-under-load OK "
        f"(trained watermark {final['trained_watermark']}/{STREAM_TOTAL}, "
        f"max lag {_inv(report, 'bounded_lag').get('max_lag_records')} "
        f"<= limit {lag_limit}, restore from peer RAM)"
    )

    # ---- stage 2: the corruption must trip bounded_lag --------------------
    report = run_chaos_job(
        _stream_config(
            os.path.join(root, "corrupt"),
            resolve_plan("none", 2),
            corrupt="drop_stream_window",
        )
    )
    if report["timed_out"]:
        return _fail("drop_stream_window run timed out (must terminate)")
    if report["invariants_ok"]:
        return _fail(
            "drop_stream_window did NOT trip the invariants — "
            "bounded_lag is not falsifiable"
        )
    inv = _inv(report, "bounded_lag")
    if inv is None or inv["status"] != "FAIL":
        return _fail(f"bounded_lag did not FAIL under the corruption: {inv}")
    print(
        "streaming_smoke: drop_stream_window trips bounded_lag OK "
        f"({inv['violations'][0]})"
    )

    # ---- stage 3: live push into a real serving CLI under hammer ----------
    import jax  # noqa: F401 — ensures the CPU backend is initialized here

    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.rpc.deadline import DeadlinePolicy
    from elasticdl_tpu.serving.replica import ServingClient
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    live_dir = os.path.join(root, "live")
    os.makedirs(live_dir, exist_ok=True)

    # boot export: a 1-step seed train (version 1) so every streaming
    # push version (task boundaries: 2, 4, 6, 8) clears the engine's
    # versioned-put guard
    seed_train = synthetic.gen_mnist(
        os.path.join(live_dir, "seed_train"),
        num_records=SERVE_BATCH,
        num_shards=1,
        seed=1,
    )
    export_v0 = os.path.join(live_dir, "export_v0")
    executor = LocalExecutor(
        parse_master_args(
            [
                "--model_def",
                "mnist_functional_api.mnist_functional_api.custom_model",
                "--training_data",
                seed_train,
                "--minibatch_size",
                str(SERVE_BATCH),
                "--records_per_task",
                str(SERVE_BATCH),
                "--num_epochs",
                "1",
                "--compute_dtype",
                "float32",
                "--output",
                export_v0,
            ]
        )
    )
    executor.run()
    v0 = int(executor.state.step)

    addr_file = os.path.join(live_dir, "serving.addr")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.serving.main",
            "--model_dir",
            export_v0,
            "--num_replicas",
            "1",
            "--port",
            "0",
            "--addr_file",
            addr_file,
            "--minibatch_size",
            str(SERVE_BATCH),
            "--max_wait_ms",
            "2",
        ],
        env=dict(os.environ),
    )
    client = None
    try:
        deadline = time.monotonic() + 120
        addr = ""
        while time.monotonic() < deadline and not addr:
            if proc.poll() is not None:
                return _fail(f"serving CLI exited rc={proc.returncode}")
            try:
                with open(addr_file, encoding="utf-8") as f:
                    addr = f.read().strip()
            except OSError:
                time.sleep(0.1)
        if not addr:
            return _fail("serving frontend never published its address")
        client = ServingClient(addr, deadlines=DeadlinePolicy.from_secs(30))

        rng = np.random.RandomState(0)

        def feats(n: int) -> dict:
            return {"image": rng.rand(n, 28, 28, 1).astype(np.float32)}

        warm = client.predict(
            msg.PredictRequest(
                request_id="warmup", features=msg.pack_array_tree(feats(SERVE_BATCH))
            )
        )
        if warm.error:
            return _fail(f"warmup predict failed: {warm.error}")
        status0 = client.serving_status()
        if status0.model_version != v0:
            return _fail(
                f"boot version {status0.model_version}, expected {v0}"
            )

        # the hammer: in-flight traffic for the WHOLE streaming run —
        # every live push lands under load
        stop = threading.Event()
        failures: list[str] = []
        hammered = [0]

        def hammer():
            i = 0
            while not stop.is_set():
                response = client.predict(
                    msg.PredictRequest(
                        request_id=f"hammer-{i}",
                        features=msg.pack_array_tree(feats(3)),
                    )
                )
                if response.error:
                    failures.append(response.error)
                hammered[0] += 1
                i += 1
                time.sleep(0.05)

        thread = threading.Thread(target=hammer, daemon=True)
        thread.start()

        report = run_chaos_job(
            _stream_config(
                os.path.join(live_dir, "run"),
                resolve_plan("none", 2),
                live_push_addr=addr,
            )
        )
        stop.set()
        thread.join(timeout=15)

        if report["rc"] != 0 or not report["invariants_ok"]:
            return _fail(
                f"live-push streaming run not green (rc={report['rc']}, "
                f"invariants_ok={report['invariants_ok']})"
            )
        if failures:
            return _fail(
                f"{len(failures)}/{hammered[0]} in-flight requests failed "
                f"across live pushes (first: {failures[0]})"
            )
        if hammered[0] == 0:
            return _fail("hammer thread never got a request through")

        fresh = (report.get("streaming") or {}).get("freshness") or {}
        if not fresh.get("accepted"):
            return _fail(f"no accepted live push in the freshness ledger: {fresh}")
        ledger = fresh.get("ledger") or []
        accepted_rows = [r for r in ledger if r["accepted"]]
        for row in accepted_rows:
            if row["staleness"] != (
                row["source_watermark"] - row["trained_watermark"]
            ) or row["staleness"] < 0:
                return _fail(f"freshness ledger row inconsistent: {row}")
        last_pushed = max(r["model_version"] for r in accepted_rows)

        status1 = client.serving_status()
        if status1.model_version <= v0:
            return _fail(
                "served version never advanced past the boot export "
                f"({status1.model_version} <= {v0}) despite "
                f"{fresh['accepted']} accepted push(es)"
            )
        if status1.model_version != last_pushed:
            return _fail(
                f"served version {status1.model_version} != last accepted "
                f"push v{last_pushed}"
            )
        if status1.compile_count != status0.compile_count:
            return _fail(
                "RECOMPILE across live pushes: compile count "
                f"{status0.compile_count} -> {status1.compile_count}"
            )

        # a replayed push must be ABSORBED, not double-applied: re-send
        # the served version (stale by the versioned-put guard) and the
        # fleet must still report convergence with the version unmoved
        from elasticdl_tpu.telemetry import events as ev

        events = ev.read_events(
            os.path.join(live_dir, "run", "telemetry", "events.jsonl")
        )
        n_push_events = sum(
            1 for e in events if e.get("event") == "live_push"
        )
        if n_push_events != fresh["pushes"]:
            return _fail(
                f"{n_push_events} live_push events vs ledger "
                f"{fresh['pushes']}"
            )

        # telemetry.report renders the same ledger (the acceptance
        # surface: staleness per swap, REFUSED marker discipline)
        from elasticdl_tpu.telemetry.report import _format_text, build_report

        run_report = build_report(os.path.join(live_dir, "run"))
        streams = [
            run.get("streaming")
            for run in run_report.get("runs", {}).values()
            if run.get("streaming")
        ]
        if not streams or not any(s.get("freshness") for s in streams):
            return _fail("telemetry.report has no streaming freshness section")
        text = _format_text(run_report)
        if "freshness:" not in text or "push v" not in text:
            return _fail(
                "telemetry.report text does not render the freshness ledger"
            )

        print(
            "streaming_smoke: live push OK "
            f"(served {v0} -> {status1.model_version} across "
            f"{fresh['accepted']} accepted / {fresh['refused']} refused "
            f"push(es), {hammered[0]} in-flight requests with 0 failures, "
            f"compile count flat at {status0.compile_count}, max staleness "
            f"{fresh['max_staleness_records']} record(s))"
        )
        return 0
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
