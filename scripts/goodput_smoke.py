#!/usr/bin/env python3
"""Tier-1 goodput smoke (wired into scripts/run_tier1.sh).

Runs a tiny LocalExecutor mnist job with ``--step_anatomy`` + telemetry
on the CPU backend THREE times — device prefetch off, prefetch on, and
prefetch + cross-task staging (``--boundary_fusion``) — and requires
the step-anatomy contract to hold in every window:

1. every dispatch emitted a ``step_anatomy`` event whose phases
   (host_fetch / assemble / h2d_transfer / device_compute /
   step_bookkeeping / untracked) sum EXACTLY to the measured dispatch
   wall time (float-noise residual only);
2. the ``untracked`` residual is under 2% of total wall — the phase
   taxonomy covers the dispatch path, it doesn't hand-wave it;
3. ``telemetry.report`` emits a ``goodput`` section whose
   ``e2e_vs_roofline`` is COMPUTED from the measured phases (a float in
   (0, 1]), with per-phase p50/p95/p99 — the measured numerator ROADMAP
   item 2's ">= 0.9" gate needs;
4. the span log carries sampled ``step_anatomy`` phase spans and
   ``trace analyze`` exposes the steady-state section (off window);
5. with ``--device_prefetch`` on, the CONSUMER-VISIBLE ``h2d_transfer``
   share is measurably lower than the prefetch-off run's (staging
   moved assembly + placement off the dispatch thread) — or already
   negligible (< 0.5% of wall, the intended end state);
6. with ``--boundary_fusion`` on top, the ``boundary_stall`` share
   (device-idle time between one task's last retire and the next
   task's first dispatch, measured per window off the heartbeat
   counter) drops versus prefetch-only — or is already negligible
   (< 0.5% of wall) — while sum-exactness still holds (the counter is
   NOT a member of the per-dispatch phase sum).

Fast by construction: 512 records, one epoch, all windows in one
process.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

UNTRACKED_GATE = 0.02
# float noise bound for the per-event sum-exactness re-check (ms)
SUM_RESIDUAL_MS = 1e-3
# an ON h2d share below this is "negligible" even if the OFF share was
# also tiny (CPU memcpy placement): the pipeline did its job
H2D_NEGLIGIBLE_SHARE = 0.005
# same rationale for the fused window's boundary-stall share
BOUNDARY_NEGLIGIBLE_SHARE = 0.005


def _run_window(
    workdir: str, train: str, prefetch: bool, fusion: bool = False
) -> dict | int:
    """One instrumented LocalExecutor window; returns the measured
    sums + report section, or a non-zero rc on a gate failure."""
    from elasticdl_tpu.telemetry import anatomy as anatomy_mod
    from elasticdl_tpu.telemetry import tracing, worker_hooks
    from elasticdl_tpu.telemetry.anatomy import TRACKED_PHASES
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.telemetry.report import build_report
    from elasticdl_tpu.trainer import device_pipeline as device_pipeline_mod
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    mode = "fused" if fusion else ("on" if prefetch else "off")
    rundir = os.path.join(workdir, f"prefetch_{mode}")
    os.makedirs(rundir, exist_ok=True)
    telemetry_dir = os.path.join(rundir, "telemetry")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "64",
            "--records_per_task",
            "128",
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
            "--steps_per_dispatch",
            "2",
            "--telemetry_dir",
            telemetry_dir,
            "--trace_sample_rate",
            "1.0",
            "--step_anatomy",
            "true",
            "--device_prefetch",
            "true" if prefetch else "false",
            "--boundary_fusion",
            "true" if fusion else "false",
        ]
    )
    # the boundary-stall totals are process-global monotone counters
    # (heartbeat-shipped in production); per-window attribution needs a
    # before/after diff
    snap_before = device_pipeline_mod.heartbeat_snapshot()
    try:
        LocalExecutor(args).run()
    finally:
        # each window installs process-global recorders bound to its
        # run dir; the next window must not inherit them
        anatomy_mod.uninstall()
        worker_hooks.uninstall()
        tracing.uninstall()
    snap_after = device_pipeline_mod.heartbeat_snapshot()
    boundary_stall_ms = snap_after.get(
        "boundary_stall_ms", 0
    ) - snap_before.get("boundary_stall_ms", 0)
    boundaries = snap_after.get("boundaries", 0) - snap_before.get(
        "boundaries", 0
    )

    events = read_events(os.path.join(telemetry_dir, "events.jsonl"))
    anat = [e for e in events if e.get("event") == "step_anatomy"]
    if not anat:
        print(
            f"goodput_smoke[{mode}]: no step_anatomy events",
            file=sys.stderr,
        )
        return 1

    # 1. sum-exactness, re-derived from the raw events
    wall_total = 0.0
    untracked_total = 0.0
    h2d_total = 0.0
    for event in anat:
        wall = float(event["wall_ms"])
        tracked = sum(
            float(event.get(f"{p}_ms", 0.0)) for p in TRACKED_PHASES
        )
        untracked = float(event.get("untracked_ms", 0.0))
        residual = abs(wall - (tracked + untracked))
        if residual > SUM_RESIDUAL_MS:
            print(
                f"goodput_smoke[{mode}]: phases do not sum to wall "
                f"(residual {residual:.6f}ms in {event})",
                file=sys.stderr,
            )
            return 1
        wall_total += wall
        untracked_total += untracked
        h2d_total += float(event.get("h2d_transfer_ms", 0.0))
    if not wall_total:
        print(
            f"goodput_smoke[{mode}]: zero wall time measured",
            file=sys.stderr,
        )
        return 1

    # 2. the untracked residual is bounded
    untracked_share = untracked_total / wall_total
    if untracked_share >= UNTRACKED_GATE:
        print(
            f"goodput_smoke[{mode}]: untracked residual "
            f"{untracked_share * 100:.2f}% >= "
            f"{UNTRACKED_GATE * 100:.0f}% of wall",
            file=sys.stderr,
        )
        return 1

    # 3. the report computes the goodput ledger from the events
    report = build_report(rundir)
    goodput = None
    for run in report["runs"].values():
        goodput = run.get("goodput")
        if goodput:
            break
    if not goodput:
        print(
            f"goodput_smoke[{mode}]: telemetry.report emitted no "
            "goodput section",
            file=sys.stderr,
        )
        return 1
    overall = goodput["overall"]
    roofline = overall.get("e2e_vs_roofline")
    if not isinstance(roofline, float) or not (0.0 < roofline <= 1.0):
        print(
            f"goodput_smoke[{mode}]: e2e_vs_roofline not computed "
            f"(got {roofline!r})",
            file=sys.stderr,
        )
        return 1
    for phase in ("device_compute", "host_fetch"):
        stats = overall["phases"].get(phase)
        if not stats or "p50_ms" not in stats or "p99_ms" not in stats:
            print(
                f"goodput_smoke[{mode}]: phase percentiles missing for "
                f"{phase}: {stats!r}",
                file=sys.stderr,
            )
            return 1
    if overall.get("max_sum_residual_ms", 1.0) > SUM_RESIDUAL_MS:
        print(
            f"goodput_smoke[{mode}]: report's own residual check "
            f"failed: {overall.get('max_sum_residual_ms')}ms",
            file=sys.stderr,
        )
        return 1

    return {
        "telemetry_dir": telemetry_dir,
        "overall": overall,
        "roofline": roofline,
        "untracked_share": untracked_share,
        "h2d_share": h2d_total / wall_total,
        # boundary_stall is a COUNTER, deliberately outside the phase
        # sum; its share of the same measured wall is the comparable
        "boundary_share": boundary_stall_ms / wall_total,
        "boundaries": boundaries,
    }


def main() -> int:
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.telemetry import trace as trace_cli
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_STEP_ANATOMY,
        SPANS_FILENAME,
        read_spans,
    )

    with tempfile.TemporaryDirectory() as workdir:
        train = synthetic.gen_mnist(
            os.path.join(workdir, "train"),
            num_records=512,
            num_shards=1,
            seed=7,
        )
        off = _run_window(workdir, train, prefetch=False)
        if isinstance(off, int):
            return off
        on = _run_window(workdir, train, prefetch=True)
        if isinstance(on, int):
            return on
        fused = _run_window(workdir, train, prefetch=True, fusion=True)
        if isinstance(fused, int):
            return fused

        # 4. sampled phase spans + the analyzer's steady-state section —
        # gated in EVERY window, so the pipelined and fused (production)
        # paths' trace output is validated too, not just the serial
        # baseline
        for mode, window in (("off", off), ("on", on), ("fused", fused)):
            spans = read_spans(
                os.path.join(window["telemetry_dir"], SPANS_FILENAME)
            )
            if not any(
                s.get("span") == SPAN_STEP_ANATOMY for s in spans
            ):
                print(
                    f"goodput_smoke[{mode}]: no step_anatomy spans in "
                    "the trace",
                    file=sys.stderr,
                )
                return 1
            analysis = trace_cli.analyze_telemetry_dir(
                window["telemetry_dir"]
            )
            if not analysis.get("steady_state"):
                print(
                    f"goodput_smoke[{mode}]: trace analyze has no "
                    "steady_state section",
                    file=sys.stderr,
                )
                return 1

        # 5. pipelining moved staging off the dispatch thread: the
        # consumer-visible h2d share must DROP (or be negligible)
        if not (
            on["h2d_share"] < off["h2d_share"]
            or on["h2d_share"] < H2D_NEGLIGIBLE_SHARE
        ):
            print(
                "goodput_smoke: --device_prefetch did not lower the "
                f"consumer-visible h2d share (off "
                f"{off['h2d_share'] * 100:.2f}% -> on "
                f"{on['h2d_share'] * 100:.2f}%)",
                file=sys.stderr,
            )
            return 1

        # 6. cross-task staging closed the dispatch gap between tasks:
        # the boundary-stall share must DROP versus prefetch-only (or
        # already be negligible)
        if not (
            fused["boundary_share"] < on["boundary_share"]
            or fused["boundary_share"] < BOUNDARY_NEGLIGIBLE_SHARE
        ):
            print(
                "goodput_smoke: --boundary_fusion did not lower the "
                f"boundary-stall share (on "
                f"{on['boundary_share'] * 100:.2f}% -> fused "
                f"{fused['boundary_share'] * 100:.2f}%)",
                file=sys.stderr,
            )
            return 1

    print(
        "goodput_smoke: OK (off: {} dispatches, roofline {:.3f}, h2d "
        "{:.2f}%, untracked {:.2f}%, bstall {:.2f}% | on: {} "
        "dispatches, roofline {:.3f}, h2d {:.2f}%, untracked {:.2f}%, "
        "bstall {:.2f}% | fused: {} dispatches, roofline {:.3f}, h2d "
        "{:.2f}%, untracked {:.2f}%, bstall {:.2f}% over {} "
        "boundaries)".format(
            off["overall"]["dispatches"],
            off["roofline"],
            off["h2d_share"] * 100.0,
            off["untracked_share"] * 100.0,
            off["boundary_share"] * 100.0,
            on["overall"]["dispatches"],
            on["roofline"],
            on["h2d_share"] * 100.0,
            on["untracked_share"] * 100.0,
            on["boundary_share"] * 100.0,
            fused["overall"]["dispatches"],
            fused["roofline"],
            fused["h2d_share"] * 100.0,
            fused["untracked_share"] * 100.0,
            fused["boundary_share"] * 100.0,
            fused["boundaries"],
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
