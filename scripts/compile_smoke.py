#!/usr/bin/env python3
"""Tier-1 compile-count smoke (wired into scripts/run_tier1.sh).

The compile-once guarantee of shape-canonical batching
(docs/designs/shape_canonicalization.md): a LocalExecutor run over
several tasks whose sizes produce DISTINCT ragged tail lengths must
execute the whole step stream with

1. backend compiles ONLY inside the first dispatch of each program kind
   (first single weighted step, first stacked scan) — every later
   dispatch, including every tail, compiles nothing ("zero mid-task
   recompiles");
2. at most 2 compile-bearing train dispatches total (the train-step
   program plus the one scan-k variant);
3. a positive process-wide ``compile_tracker`` total (the counter that
   feeds ``elasticdl_compile_total``) and at least one ``compile`` span
   in the trace log.

Geometry: 24 mnist records, records_per_task=9, minibatch=4 ->
tasks of 9, 9 and 6 records = batch streams (4,4,1), (4,4,1), (4,2) —
two distinct tail lengths (1 and 2) — with ``--steps_per_dispatch 2``
exercising both the stacked scan and the single-step path.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.parallel.distributed import SPMDTrainer
    from elasticdl_tpu.telemetry import compile_tracker
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_COMPILE,
        read_spans,
    )
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    if not compile_tracker.install():
        print("compile_smoke: no compile hook available", file=sys.stderr)
        return 1

    with tempfile.TemporaryDirectory() as workdir:
        train = synthetic.gen_mnist(
            os.path.join(workdir, "train"),
            num_records=24,
            num_shards=1,
            seed=1,
        )
        telemetry_dir = os.path.join(workdir, "telemetry")
        args = parse_master_args(
            [
                "--model_def",
                "mnist_functional_api.mnist_functional_api.custom_model",
                "--training_data",
                train,
                "--minibatch_size",
                "4",
                "--records_per_task",
                "9",
                "--num_epochs",
                "1",
                "--steps_per_dispatch",
                "2",
                "--compute_dtype",
                "float32",
                "--telemetry_dir",
                telemetry_dir,
                "--trace_sample_rate",
                "1.0",
            ]
        )
        executor = LocalExecutor(args)

        # observe compiles per train dispatch by wrapping the two step
        # entry points (the counter is process-wide; snapshotting around
        # each dispatch isolates the train programs from init/utility
        # compiles)
        dispatch_log: list[tuple[str, int]] = []
        orig_single = SPMDTrainer.train_step
        orig_stacked = SPMDTrainer.train_steps_stacked

        def single(self, *a, **kw):
            before = compile_tracker.compile_count()
            result = orig_single(self, *a, **kw)
            dispatch_log.append(
                ("single", compile_tracker.compile_count() - before)
            )
            return result

        def stacked(self, *a, **kw):
            before = compile_tracker.compile_count()
            result = orig_stacked(self, *a, **kw)
            dispatch_log.append(
                ("stacked", compile_tracker.compile_count() - before)
            )
            return result

        SPMDTrainer.train_step = single
        SPMDTrainer.train_steps_stacked = stacked
        try:
            executor.run()
        finally:
            SPMDTrainer.train_step = orig_single
            SPMDTrainer.train_steps_stacked = orig_stacked

        if executor.state is None or int(executor.state.step) != 8:
            print(
                f"compile_smoke: expected 8 steps, got "
                f"{executor.state and int(executor.state.step)}",
                file=sys.stderr,
            )
            return 1
        kinds = {kind for kind, _ in dispatch_log}
        if kinds != {"single", "stacked"}:
            print(
                f"compile_smoke: expected both dispatch kinds, got "
                f"{sorted(kinds)} ({dispatch_log})",
                file=sys.stderr,
            )
            return 1
        first_seen: set[str] = set()
        compiling_dispatches = 0
        for index, (kind, compiles) in enumerate(dispatch_log):
            is_first = kind not in first_seen
            first_seen.add(kind)
            if compiles:
                compiling_dispatches += 1
            if not is_first and compiles:
                print(
                    f"compile_smoke: RECOMPILE at dispatch {index} "
                    f"({kind}): {compiles} compiles — canonical shapes "
                    f"should reuse the program ({dispatch_log})",
                    file=sys.stderr,
                )
                return 1
        if compiling_dispatches > 2:
            print(
                f"compile_smoke: {compiling_dispatches} compile-bearing "
                f"train dispatches (> 2): {dispatch_log}",
                file=sys.stderr,
            )
            return 1
        if compile_tracker.compile_count() <= 0:
            print("compile_smoke: counter never incremented", file=sys.stderr)
            return 1
        spans = read_spans(os.path.join(telemetry_dir, "spans.jsonl"))
        compile_spans = [s for s in spans if s.get("span") == SPAN_COMPILE]
        if not compile_spans:
            print("compile_smoke: no compile spans recorded", file=sys.stderr)
            return 1
    print(
        f"compile_smoke: OK ({len(dispatch_log)} train dispatches, "
        f"{compiling_dispatches} compiled; process total "
        f"{compile_tracker.compile_count()} compiles, "
        f"{len(compile_spans)} compile spans)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
